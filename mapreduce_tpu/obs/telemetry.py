"""Telemetry facade: registry + run ledger + flight recorder as one handle.

The executor takes ONE optional ``telemetry`` object instead of three
shims; everything degrades together:

* ``Telemetry.create(ledger_path=...)`` — full telemetry: JSONL ledger,
  flight recorder armed, device-stat sampling, compile-event capture,
  writing into the process-global metrics registry.
* ``Telemetry.disabled()`` — the shared no-op instance (the default when a
  caller passes ``telemetry=None``): every hot-path method returns
  immediately on ``self.enabled``.  Disabled telemetry adds no per-step
  host sync and no per-step allocation — the acceptance bar of ISSUE 2
  (the graphcheck host-sync pass sees identical step programs either way,
  because none of this lives inside jit).

Device stats are sampled HOST-side only: ``device.memory_stats()`` is a
PJRT metadata query and ``jax.live_arrays()`` enumerates already-tracked
handles — neither blocks on device compute, so sampling at step cadence
does not serialize the async dispatch pipeline.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
import weakref
from typing import Any, Optional

from mapreduce_tpu.obs import flight as flight_mod
from mapreduce_tpu.obs import ledger as ledger_mod
from mapreduce_tpu.obs import registry as registry_mod

# ---------------------------------------------------------------------------
# Compile-event capture: jax reports compile durations through its
# monitoring hooks; a process-wide listener fans them into the default
# registry and into every live Telemetry's pending queue, so the next
# ledger step record carries the compiles that landed since the previous
# one (first-step records show the big trace+compile; later spikes reveal
# recompile hazards).  Best-effort: the hook is jax-internal, so absence
# degrades to "no compile events", never to a failure.
# ---------------------------------------------------------------------------

# Weak refs: a Telemetry handle dropped without close() must become
# garbage, not a process-lifetime leak accumulating compile events via
# the listener below (close() still removes deterministically).
_LIVE: "weakref.WeakSet[Telemetry]" = weakref.WeakSet()
_LIVE_LOCK = threading.Lock()
_LISTENER_INSTALLED = False


def _compile_listener(event: str, duration: float, **kw) -> None:
    if "compile" not in event:
        return
    registry_mod.get_registry().observe("jax.compile_seconds", duration,
                                        event=event)
    with _LIVE_LOCK:
        live = list(_LIVE)
    for tel in live:
        tel._pend_compile(event, duration)


def _install_compile_listener() -> bool:
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return True
    try:
        from jax._src import monitoring

        monitoring.register_event_duration_secs_listener(_compile_listener)
    except Exception:
        return False
    _LISTENER_INSTALLED = True
    return True


def device_memory_stats() -> dict:
    """Best-effort host-side device memory snapshot.

    Prefers the backend's ``memory_stats()`` (TPU/GPU: bytes_in_use, peak);
    always adds the ``jax.live_arrays()`` aggregate, which is the only
    signal the CPU backend has (its memory_stats is typically None).  Both
    are metadata reads — no device sync.
    """
    out: dict = {}
    try:
        import jax

        per_dev = []
        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if ms:
                per_dev.append(ms)
        if per_dev:
            out["bytes_in_use"] = int(sum(m.get("bytes_in_use", 0)
                                          for m in per_dev))
            peak = sum(m.get("peak_bytes_in_use", 0) for m in per_dev)
            if peak:
                out["peak_bytes_in_use"] = int(peak)
            out["devices_reporting"] = len(per_dev)
        arrs = jax.live_arrays()
        out["live_arrays"] = len(arrs)
        out["live_bytes"] = int(sum(getattr(a, "nbytes", 0) for a in arrs))
    except Exception:
        pass  # observing must never take down the observed run
    return out


#: Default wall-clock seconds between ``progress`` heartbeat records
#: (ISSUE 14, ledger v8).  Coarse on purpose: a tailer wants a fresh line
#: every few seconds, and anything finer just burns ledger bytes — the
#: not-due path is one monotonic read + compare (the <1 ms bound).
DEFAULT_PROGRESS_EVERY_S = 5.0


class Telemetry:
    """One handle over the three telemetry planes.  See module docstring."""

    def __init__(self, *, enabled: bool = True,
                 registry: Optional[registry_mod.MetricsRegistry] = None,
                 ledger: Optional[ledger_mod.RunLedger] = None,
                 flight: Optional[flight_mod.FlightRecorder] = None,
                 flight_path: Optional[str] = None,
                 sample_device_stats: bool = True,
                 progress_every_s: float = DEFAULT_PROGRESS_EVERY_S):
        self.enabled = enabled
        self.registry = registry if registry is not None \
            else registry_mod.get_registry()
        self.ledger = ledger
        self.flight = flight
        self.flight_path = flight_path
        self.sample_device_stats = sample_device_stats
        self.run_id = ledger.run_id if ledger is not None \
            else uuid.uuid4().hex[:12]
        # Multi-host attachment (ISSUE 13, ledger v7): the per-record host
        # stamp, the run_start topology/clock extras, and the per-host
        # shard ledger.  All empty/None on single-host runs, so their
        # ledgers keep the exact pre-v7 record shapes.
        self.host: dict = {}
        self.topology: Optional[dict] = None
        self.shard: Optional[ledger_mod.RunLedger] = None
        # Latest data-plane summary (ISSUE 8): the executor updates it at
        # every group retirement, so a flight dump on the failure path
        # carries the run's data-health snapshot as of the crash.
        self.last_data: Optional[dict] = None
        # Latest autotune recommendation (ISSUE 10): set once per hint
        # run, so callers that never see the RunResult (the CLI's
        # count_file path) can still surface the recommendation.
        self.last_tune: Optional[dict] = None
        # Live-run heartbeat state (ISSUE 14, ledger v8): the wall-clock
        # cadence gate and the stream-start baseline ETA math reads from.
        self.progress_every_s = float(progress_every_s)
        self._last_progress_t: Optional[float] = None
        self._progress_t0: Optional[float] = None
        self._last_phases: dict = {}
        self._last_record_t: Optional[float] = None
        self._pending_compiles: list = []
        self._pending_lock = threading.Lock()
        if enabled:
            _install_compile_listener()
            with _LIVE_LOCK:
                _LIVE.add(self)

    # -- construction ------------------------------------------------------

    @classmethod
    def create(cls, ledger_path: Optional[str] = None,
               registry: Optional[registry_mod.MetricsRegistry] = None,
               flight_capacity: int = flight_mod.DEFAULT_CAPACITY,
               flight_path: Optional[str] = None,
               run_id: Optional[str] = None,
               progress_every_s: float = DEFAULT_PROGRESS_EVERY_S) \
            -> "Telemetry":
        """Full telemetry.  ``flight_path`` defaults next to the ledger
        (``<ledger>.flight.json``) so one flag leaves both artifacts.
        ``progress_every_s`` sets the live-run heartbeat cadence
        (ISSUE 14; 0 emits at every opportunity — test/tail-demo use)."""
        rid = run_id or uuid.uuid4().hex[:12]
        ledger = ledger_mod.RunLedger(ledger_path, rid) if ledger_path else None
        if flight_path is None and ledger_path:
            flight_path = ledger_path + ".flight.json"
        return cls(enabled=True, registry=registry, ledger=ledger,
                   flight=flight_mod.FlightRecorder(flight_capacity),
                   flight_path=flight_path,
                   progress_every_s=progress_every_s)

    _DISABLED: "Optional[Telemetry]" = None

    @classmethod
    def disabled(cls) -> "Telemetry":
        """The shared no-op instance (zero per-step work)."""
        if cls._DISABLED is None:
            cls._DISABLED = cls(enabled=False, sample_device_stats=False)
        return cls._DISABLED

    # -- multi-host attachment (ISSUE 13) ---------------------------------

    def attach_host(self, process_index: int, process_count: int, *,
                    local_devices: Optional[int] = None,
                    clock: Optional[dict] = None,
                    shard: bool = True) -> None:
        """Join this handle to a multi-host fleet (ledger v7).

        Every subsequent ledger record is stamped with this process's
        ``host`` index; ``run_start`` additionally carries the process/
        device topology and the ``clock`` {wall, mono} pair (sampled at
        ``jax.distributed`` init — ``parallel.distributed.run_epoch``)
        that ``obs/fleet.py`` uses to rebase monotonic lifecycle stamps
        onto the shared wall clock.  With ``shard=True`` (the global-SPMD
        driver) the per-host shard ledger ``<ledger>.h<p>.jsonl`` opens
        next to the main file and receives EVERY record regardless of the
        coordinator write gate; non-coordinator processes also re-point
        the flight recorder at the host-suffixed dump path, so a remote
        failure leaves forensics from the host that actually failed.
        ``shard=False`` (the per-host-driven mode, where each host owns
        its whole ledger file already) stamps without a second file.
        """
        if not self.enabled:
            return
        self.host = {"host": int(process_index)}
        self.topology = {"processes": int(process_count)}
        if local_devices is not None:
            self.topology["local_devices"] = int(local_devices)
        if clock is not None:
            self.topology["clock"] = dict(clock)
        if shard and self.ledger is not None and self.shard is None:
            self.shard = ledger_mod.RunLedger(
                ledger_mod.shard_path(self.ledger.path, process_index),
                self.ledger.run_id)
        if shard and process_index != 0:
            # Re-point the flight recorder even when no ledger is
            # attached: in shard mode every process shares one path by
            # contract, and N processes racing one flight.json would
            # shred the failing host's forensics.
            if self.ledger is not None:
                self.flight_path = ledger_mod.shard_flight_path(
                    self.ledger.path, process_index)
            elif self.flight_path:
                self.flight_path = f"{self.flight_path}.h{process_index}"

    # -- compile-event plumbing -------------------------------------------

    def _pend_compile(self, event: str, duration: float) -> None:
        with self._pending_lock:
            self._pending_compiles.append((event, duration))

    def _drain_compiles(self) -> dict:
        """Pending compile events AGGREGATED per event type.  jax emits
        hundreds of sub-millisecond trace events per program; the ledger
        wants "this window compiled, and it cost N seconds", while the
        registry histogram keeps the full distribution."""
        with self._pending_lock:
            pending, self._pending_compiles = self._pending_compiles, []
        out: dict = {}
        for event, duration in pending:
            short = event.rsplit("/", 1)[-1]
            agg = out.setdefault(short, {"count": 0, "seconds": 0.0})
            agg["count"] += 1
            agg["seconds"] += duration
        for agg in out.values():
            agg["seconds"] = round(agg["seconds"], 4)
        return out

    # -- event surface (all no-ops when disabled) --------------------------

    def event(self, kind: str, **fields) -> None:
        """Record into the flight ring (cheap; not a ledger write)."""
        if self.enabled and self.flight is not None:
            self.flight.record(kind, **fields)

    def ledger_write(self, kind: str, write: bool = True, **fields) -> None:
        """Write one record to the run ledger(s).  ``write=False`` (a
        process that does not hold the multi-host write gate) skips the
        merged-authoritative main file but still writes the per-host
        shard when one is attached — in a fleet every process keeps its
        own record (ISSUE 13)."""
        if not self.enabled:
            return
        if self.host:
            fields = {**self.host, **fields}
        if kind == "run_start" and self.topology:
            fields = {**fields, **self.topology}
        if write and self.ledger is not None:
            self.ledger.write(kind, **fields)
        if self.shard is not None:
            self.shard.write(kind, **fields)

    def step_record(self, *, step_first: int, step_last: int,
                    group_bytes: int, cursor_bytes: int, timer,
                    retries: int = 0, write: bool = True,
                    inflight_depth: Optional[int] = None) -> None:
        """One ledger step record: phase-second DELTAS since the previous
        record (the timer accumulates run totals), elapsed wall-clock,
        device memory stats, and any compile events that landed in the
        window.  ``inflight_depth`` (ISSUE 5): how many dispatch groups
        were in flight right after this one was enqueued — the per-step
        sample behind the run-end depth statistics.  ``write=False``
        (non-coordinator processes in multi-host runs) still advances the
        delta baseline so a later gate flip never reports a cumulative
        blob as one step — and still lands the record in the per-host
        shard ledger when one is attached (ISSUE 13)."""
        if not self.enabled:
            return
        phases = {k: round(v - self._last_phases.get(k, 0.0), 6)
                  for k, v in timer.phases.items()
                  if v - self._last_phases.get(k, 0.0) > 0}
        self._last_phases = dict(timer.phases)
        now = time.perf_counter()
        elapsed = None if self._last_record_t is None \
            else round(now - self._last_record_t, 6)
        self._last_record_t = now
        compiles = self._drain_compiles()
        steps = step_last - step_first + 1
        self.registry.counter("executor.steps").inc(steps)
        self.registry.counter("executor.dispatch_groups").inc()
        self.registry.counter("executor.bytes_streamed").inc(group_bytes)
        if "dispatch" in phases:
            self.registry.observe("executor.dispatch_seconds",
                                  phases["dispatch"])
        self.event("step", step_first=step_first, step_last=step_last,
                   cursor_bytes=cursor_bytes)
        if not ((write and self.ledger is not None)
                or self.shard is not None):
            return
        mem = device_memory_stats() if self.sample_device_stats else {}
        rec: dict[str, Any] = dict(step_first=step_first, step_last=step_last,
                                   steps=steps, group_bytes=group_bytes,
                                   cursor_bytes=cursor_bytes, phases=phases,
                                   mem=mem)
        if elapsed is not None:
            rec["elapsed_s"] = elapsed
        if retries:
            rec["retries"] = retries
        if inflight_depth is not None:
            rec["inflight_depth"] = inflight_depth
        if compiles:
            rec["compile_events"] = compiles
        self.ledger_write("step", write=write, **rec)

    def progress(self, *, step: int, cursor_bytes: int, streamed_bytes: int,
                 total_bytes: Optional[int] = None,
                 groups_dispatched: Optional[int] = None,
                 groups_retired: Optional[int] = None,
                 inflight_depth: Optional[int] = None,
                 write: bool = True, force: bool = False) -> bool:
        """The live-run heartbeat (ISSUE 14, ledger v8): one ``progress``
        record per :attr:`progress_every_s` of wall clock — the stream
        cursor, completion fraction, groups dispatched/retired, current
        in-flight depth, throughput-so-far, and the ETA derived from the
        byte cursor.  Pure host-side bookkeeping: no device wait, no
        memory-stat sampling, and the not-due path is one monotonic read
        + compare, so the dispatch loop can call it per group for free
        (the <1 ms emission bound extends the PR-7/8 overhead bound).
        Flushed like every ledger record, so ``tools/obswatch.py`` sees
        it while the run is still in flight.  Returns True when a record
        was written; always False with no ledger/shard attached (there
        is nothing to tail)."""
        if not self.enabled or (self.ledger is None and self.shard is None):
            return False
        now = time.monotonic()
        if self._progress_t0 is None:
            self._progress_t0 = now
        if not force and self._last_progress_t is not None \
                and now - self._last_progress_t < self.progress_every_s:
            return False
        self._last_progress_t = now
        elapsed = now - self._progress_t0
        rec: dict[str, Any] = {"step": int(step),
                               "cursor_bytes": int(cursor_bytes),
                               "streamed_bytes": int(streamed_bytes),
                               "elapsed_s": round(elapsed, 6)}
        if total_bytes:
            rec["total_bytes"] = int(total_bytes)
            rec["frac"] = round(min(1.0, int(streamed_bytes)
                                    / int(total_bytes)), 6)
        if elapsed > 0 and streamed_bytes:
            rate = int(streamed_bytes) / elapsed
            rec["bytes_per_s"] = round(rate, 1)
            rec["gb_per_s"] = round(rate / 1e9, 6)
            if total_bytes and int(total_bytes) > int(streamed_bytes):
                rec["eta_s"] = round(
                    (int(total_bytes) - int(streamed_bytes)) / rate, 3)
        if groups_dispatched is not None:
            rec["groups_dispatched"] = int(groups_dispatched)
        if groups_retired is not None:
            rec["groups_retired"] = int(groups_retired)
        if inflight_depth is not None:
            rec["inflight_depth"] = int(inflight_depth)
        self.ledger_write("progress", write=write, **rec)
        return True

    def note_data(self, data: Optional[dict]) -> None:
        """Record the latest data-plane run summary (ISSUE 8) so the
        flight recorder's failure dump carries it.  A dict assignment —
        no I/O, no device work; no-op when disabled."""
        if self.enabled and data is not None:
            self.last_data = data

    def note_tune(self, tune: Optional[dict]) -> None:
        """Record the run's autotune recommendation (ISSUE 10) so
        result-dropping call paths (the CLI) can still report it.  A
        dict assignment; no-op when disabled."""
        if self.enabled and tune is not None:
            self.last_tune = tune

    def flight_dump(self, context: Optional[dict] = None,
                    state: Any = None) -> Optional[str]:
        """Dump the flight ring + state summary + registry snapshot —
        plus the latest data-plane summary and its health classification
        (ISSUE 8), so a crashed run's forensics say what the DATA was
        doing, not just what the host loop was.  Returns the dump path
        (None when telemetry is off or pathless).  Idempotent: the first
        failure of a run owns the file."""
        if not (self.enabled and self.flight is not None and self.flight_path):
            return None
        summary = None
        if state is not None:
            try:
                summary = flight_mod.summarize_state(state)
            except Exception:
                summary = {"error": "state summary failed"}
        data_health = None
        if self.last_data is not None:
            try:  # jax-free classifier; a dump must never mask the failure
                from mapreduce_tpu.obs import datahealth

                data_health = datahealth.classify(self.last_data)
            except Exception:
                data_health = {"error": "classification failed"}
        return self.flight.dump(self.flight_path, context=context,
                                state_summary=summary,
                                registry_snapshot=self.registry.snapshot(),
                                data=self.last_data,
                                data_health=data_health)

    def close(self) -> None:
        """Flush/close the ledger(s) and stop receiving compile events."""
        with _LIVE_LOCK:
            _LIVE.discard(self)
        if self.ledger is not None:
            self.ledger.close()
        if self.shard is not None:
            self.shard.close()

    def __enter__(self) -> "Telemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def maybe(telemetry: Optional[Telemetry]) -> Telemetry:
    """Normalize an optional telemetry argument to a usable handle."""
    return telemetry if telemetry is not None else Telemetry.disabled()


def default_flight_path() -> str:
    """Fallback dump location when a run has telemetry but no ledger path."""
    import tempfile

    return os.path.join(tempfile.gettempdir(),
                        f"mapreduce-flight-{os.getpid()}.json")
