#!/usr/bin/env python3
"""Fleet observability: merge per-host ledger shards into one cross-host
timeline with straggler/collective accounting (ISSUE 13 tentpole).

Multi-host runs write one shard ledger per process
(``<ledger>.h<process_index>.jsonl``, ledger v7) next to the coordinator's
merged-authoritative main file; each shard's records carry monotonic
lifecycle stamps from that process's own clock.  This module:

* **aligns** the shards onto one time base: each shard's ``run_start``
  carries a ``clock`` {wall, mono} pair sampled at ``jax.distributed``
  init (``parallel.distributed.run_epoch``), so every monotonic stamp
  rebases to the shared wall clock
  (``aligned_stamp = stamp + (wall_epoch - mono_epoch)``);
  when any shard predates the clock stamp the raw monotonic values are
  kept (correct for same-box processes: Linux ``CLOCK_MONOTONIC`` is
  system-wide) and the artifact says ``aligned: false``;
* **reconstructs** per-host resource lanes through
  :func:`timeline.reconstruct` (``with_collective=True``: the per-run
  ``collective`` records become a ``collective`` lane);
* computes the **cross-host straggler decomposition**: per-superstep host
  skew (latest minus earliest ``token_ready_at`` across hosts),
  slowest-host attribution (which host ran latest, and by how much in
  total), and per-host lag totals;
* accounts the **collective** time (the observed finish intervals, per
  host and fleet mean);
* emits the **fleet_bottleneck verdict** — ``straggler-bound`` (the skew
  is the bigger recoverable cost: a perfectly balanced fleet saves
  ~total_skew_s), ``collective-bound`` (the collective finish is), or
  ``balanced`` (neither clears 10% of the fleet span) — with the
  projected saving, the machine-readable signal the ROADMAP-item-3
  reduction-strategy planner (and the autotuner's trail note) consumes;
* classifies **host imbalance** from per-host data counters (the
  ``host_bytes`` group fields + ``data`` record tokens) via
  :func:`datahealth.classify_fleet`;
* renders the whole fleet as Chrome trace-event JSON with **one Perfetto
  pid per host** (one tid per lane inside it).

Shard pairing: each shard contributes its LAST run by default (multi-
controller SPMD processes execute runs in lockstep, so the same ordinal
is the same fleet run even when per-process ``run_id``s differ — pass the
same ``run_id`` to every process's Telemetry to make the pairing
explicit, or ``--run-id`` here to select one).

The merged record stream (``--merged``) is deterministic — shard streams
concatenated in host order (each shard is already in write order) plus
one synthesized ``fleet`` record carrying the verdicts — so two merge
invocations over the same shards are byte-identical, and the autotuner's
``derive_signals`` can read ``fleet_bottleneck`` from the merged file.

Deliberately jax-free and stdlib-only (the ``obs/timeline.py`` contract):
runnable as a script (``python mapreduce_tpu/obs/fleet.py``) on a box
with neither jax nor the package installed — sibling modules load by
file path.  ``--selftest`` runs the checked-in two-host shard fixtures
(``tools/fixtures/fleet_ledger.h*.jsonl``) against hand arithmetic; it
is wired into ``tools/tier1.sh`` and ``tools/smoke.sh``.

Usage::

    python mapreduce_tpu/obs/fleet.py /path/run.jsonl            # summary
    python mapreduce_tpu/obs/fleet.py /path/run.jsonl --json     # artifact
    python mapreduce_tpu/obs/fleet.py /path/run.jsonl --trace out.json
    python mapreduce_tpu/obs/fleet.py /path/run.jsonl --merged merged.jsonl
    python mapreduce_tpu/obs/fleet.py a.h0.jsonl a.h1.jsonl      # explicit
    python mapreduce_tpu/obs/fleet.py --selftest
"""

from __future__ import annotations

import argparse
import glob as glob_mod
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Tuple

if __package__:
    from mapreduce_tpu.obs import datahealth, timeline
    from mapreduce_tpu.obs import ledger as ledger_mod
else:  # script / by-path execution: load the jax-free siblings by path
    import importlib.util

    def _load_sibling(name: str):
        p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         name + ".py")
        spec = importlib.util.spec_from_file_location(
            f"_mapreduce_tpu_fleet_{name}", p)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    timeline = _load_sibling("timeline")
    datahealth = _load_sibling("datahealth")
    ledger_mod = _load_sibling("ledger")

#: Recoverable seconds (straggler skew or collective time) below this
#: share of the fleet span read as ``balanced``: the fleet is within 10%
#: of its balance ceiling and the verdict should not send anyone chasing
#: noise (the timeline verdict's converged threshold, applied fleet-wide).
FLEET_MIN_FRAC = 0.10

#: Monotonic-stamp fields rebased by clock alignment (group lifecycle +
#: collective intervals).  Unknown future stamp fields stay untouched —
#: a reader must never guess a field's clock.
ALIGN_FIELDS = ("read_at", "staged_at", "dispatched_at", "token_ready_at",
                "retired_at", "h2d_done_at", "started_at", "ended_at")

_SHARD_RE = re.compile(r"\.h(\d+)\.jsonl$")


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def read_jsonl(path: str) -> List[dict]:
    """Parse a shard file through the one tolerant JSONL reader
    (``obs/ledger.read_ledger``: unparseable lines are crash forensics,
    not errors), keeping dict records only."""
    return [r for r in ledger_mod.read_ledger(path) if isinstance(r, dict)]


def shard_paths(ledger_path: str) -> Dict[int, str]:
    """Discover ``<ledger>.h<p>.jsonl`` shard files next to a main ledger
    path (which itself need not exist)."""
    out: Dict[int, str] = {}
    for p in glob_mod.glob(glob_mod.escape(ledger_path) + ".h*.jsonl"):
        m = _SHARD_RE.search(p)
        if m:
            out[int(m.group(1))] = p
    return out


def load_shards(paths: Iterable[str]) -> Dict[int, List[dict]]:
    """Explicit shard files -> ``{host: records}``.  The host index comes
    from the ``.h<p>.jsonl`` suffix when present, else from position (a
    mode-(a) per-host ledger is a shard at a user-chosen path)."""
    out: Dict[int, List[dict]] = {}
    for i, p in enumerate(paths):
        m = _SHARD_RE.search(p)
        host = int(m.group(1)) if m else i
        while host in out:  # positional fallback collision: next free slot
            host += 1
        out[host] = read_jsonl(p)
    return out


def split_instances(records: Iterable[dict]) \
        -> List[Tuple[Optional[str], int, List[dict]]]:
    """An append-mode record stream -> ``[(run_id, instance, records)]``
    in first-appearance order — the CANONICAL run-instance splitter
    (``obs/history.py``, ``tools/obs_report.py`` and ``tools/
    obswatch.py`` all consume this one rule).

    Instances, not just ids: the documented multi-host contract passes
    the SAME ``run_id`` to every process, ledger files are append-mode,
    and a crash+relaunch recovery appends a second run under that id —
    every ``run_start`` opens a NEW instance, so the crashed attempt and
    its recovery never fuse into one corrupt view (a file's records are
    sequential: one writer, runs never interleave)."""
    out: List[Tuple[Optional[str], int, List[dict]]] = []
    current: Dict = {}  # run_id -> index of its open instance
    for r in records:
        if not isinstance(r, dict):
            continue
        rid = r.get("run_id")
        if r.get("kind") == "run_start" or rid not in current:
            current[rid] = len(out)
            out.append((rid, sum(1 for x in out if x[0] == rid), []))
        out[current[rid]][2].append(r)
    return out


def run_status(completed: bool, failures: int) -> str:
    """The ONE completed/crashed/in-flight rule (a ``run_end`` record =
    completed; a ``failure`` record with no ``run_end`` after = crashed;
    neither = still going, or the process died without the failure path
    running).  ``obs_report --list-runs``, ``tools/obswatch.py`` and the
    ``obs/history.py`` digests all classify through this predicate."""
    if completed:
        return "completed"
    return "crashed" if failures else "in-flight"


def select_run(records: List[dict],
               run_id: Optional[str] = None) -> Tuple[Optional[str],
                                                      List[dict]]:
    """One shard's records of one RUN INSTANCE: ``run_id`` when given
    (its last instance), else the shard's last instance overall —
    derived from :func:`split_instances`."""
    runs = split_instances(records)
    if run_id is not None:
        mine = [r for r in runs if r[0] == run_id]
        return run_id, (mine[-1][2] if mine else [])
    if not runs:
        return None, []
    rid, _, recs = runs[-1]
    return rid, recs


def clock_offset(records: Iterable[dict]) -> Optional[float]:
    """This shard's monotonic->wall offset from its run_start ``clock``
    pair, or None when the shard predates the v7 stamp."""
    for r in records:
        if r.get("kind") != "run_start":
            continue
        clock = r.get("clock")
        if isinstance(clock, dict):
            wall, mono = _num(clock.get("wall")), _num(clock.get("mono"))
            if wall is not None and mono is not None:
                return wall - mono
        return None
    return None


def align(records: List[dict], offset: float) -> List[dict]:
    """Copies of ``records`` with every monotonic stamp field rebased by
    ``offset`` (no-op copies at offset 0)."""
    if not offset:
        return [dict(r) for r in records]
    out = []
    for r in records:
        r = dict(r)
        for f in ALIGN_FIELDS:
            v = _num(r.get(f))
            if v is not None:
                r[f] = round(v + offset, 6)
        out.append(r)
    return out


def _select_aligned(by_host: Dict[int, List[dict]],
                    run_id: Optional[str] = None):
    """``{host: records}`` -> ``({host: (run_id, aligned records)},
    aligned_flag)`` — the shared selection + alignment step.  Alignment
    applies only when EVERY participating shard carries a clock pair
    (mixing rebased and raw stamps would fabricate skew)."""
    sel: Dict[int, Tuple[Optional[str], List[dict]]] = {}
    for h in sorted(by_host):
        rid, recs = select_run(by_host[h], run_id)
        if recs:
            sel[h] = (rid, recs)
    if not sel:
        return {}, False
    offsets = {h: clock_offset(recs) for h, (_, recs) in sel.items()}
    aligned = all(offsets[h] is not None for h in sel)
    return {h: (rid, align(recs, offsets[h] if aligned else 0.0))
            for h, (rid, recs) in sel.items()}, aligned


def _intervals(recs: List[dict], rid: Optional[str]):
    """All absolute (aligned) lane intervals of one host's run — the
    span/trace raw material: ``[(lane, start, end, record), ...]``."""
    out = []
    for rec in timeline.iter_groups(recs, rid):
        iv = timeline.group_intervals(rec)
        if iv:
            for lane, (s, e) in iv.items():
                out.append((lane, s, e, rec))
    for rec in timeline.iter_collectives(recs, rid):
        iv = timeline.collective_interval(rec)
        if iv is not None:
            out.append(("collective", iv[0], iv[1], rec))
    return out


def _overlap_seconds(spans, others) -> float:
    """Seconds of ``spans`` covered by the union of ``others`` — the
    overlap-HIDDEN share of a host's collective time (ISSUE 20): a
    window-boundary partial merge in flight while the host's other lanes
    stay busy costs no exclusive wall-clock, so the fleet verdict charges
    only the visible remainder."""
    if not spans or not others:
        return 0.0
    merged: List[List[float]] = []
    for s, e in sorted(others):
        if merged and s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([s, e])
    total = 0.0
    for s, e in spans:
        for ms, me in merged:
            lo, hi = max(s, ms), min(e, me)
            if lo < hi:
                total += hi - lo
    return total


def fleet_view(by_host: Dict[int, List[dict]],
               run_id: Optional[str] = None, *,
               selected=None) -> Optional[dict]:
    """Shard records -> the fleet artifact (see module docstring), or
    None when no shard carries usable records.  ``selected`` lets a
    caller reuse one :func:`_select_aligned` result across view/trace/
    merge (alignment deep-copies every record — do it once)."""
    sel, aligned = selected if selected is not None \
        else _select_aligned(by_host, run_id)
    if not sel:
        return None
    hosts = sorted(sel)
    arts = {h: timeline.reconstruct(recs, run_id=rid, with_collective=True)
            for h, (rid, recs) in sel.items()}

    # Per-superstep straggler decomposition: each host's observed finish
    # (token_ready_at) per step_first, on the shared clock.
    finishes: Dict[int, Dict[int, float]] = {}
    per_host: Dict[str, dict] = {}
    all_iv: List = []
    for h in hosts:
        rid, recs = sel[h]
        iv = _intervals(recs, rid)
        all_iv.extend(iv)
        groups = bytes_total = host_bytes = 0
        have_host_bytes = False
        for rec in timeline.iter_groups(recs, rid):
            t = _num(rec.get("token_ready_at"))
            sf = rec.get("step_first")
            if t is not None and isinstance(sf, int):
                finishes.setdefault(sf, {})[h] = t
            groups += 1
            bytes_total += int(_num(rec.get("group_bytes")) or 0)
            hb = _num(rec.get("host_bytes"))
            if hb is not None:
                have_host_bytes = True
                host_bytes += int(hb)
        coll_spans = [(s, e) for lane, s, e, _ in iv if lane == "collective"]
        other_spans = [(s, e) for lane, s, e, _ in iv
                       if lane != "collective"]
        coll = sum(e - s for s, e in coll_spans)
        # Overlap accounting (ISSUE 20): window-boundary partial merges
        # run while the map lanes are still busy — that hidden share
        # costs no exclusive wall-clock, so the verdict below charges
        # only the visible remainder (the total stays in collective_s).
        hidden = _overlap_seconds(coll_spans, other_spans)
        tokens = sum(int(_num(r.get("tokens")) or 0) for r in recs
                     if r.get("kind") == "data")
        art = arts.get(h)
        per_host[str(h)] = {
            "run_id": rid,
            "groups": groups,
            "group_bytes": bytes_total,
            "host_bytes": host_bytes if have_host_bytes else None,
            "tokens": tokens or None,
            "device_busy_s": (art or {}).get("lane_busy_s", {}).get(
                "device", 0.0),
            "collective_s": round(coll, 6),
            "collective_hidden_s": round(hidden, 6),
            "collective_visible_s": round(coll - hidden, 6),
            "bottleneck": ((art or {}).get("bottleneck") or {}).get(
                "resource"),
        }
    if not all_iv:
        return None
    t0 = min(s for _, s, _, _ in all_iv)
    t_end = max(e for _, _, e, _ in all_iv)
    span = t_end - t0

    supersteps = []
    lag: Dict[int, float] = {h: 0.0 for h in hosts}
    slow_wins: Dict[int, int] = {h: 0 for h in hosts}
    total_skew = 0.0
    for sf in sorted(finishes):
        f = finishes[sf]
        if len(f) < 2:
            continue
        fastest, latest = min(f.values()), max(f.values())
        slowest = min(h for h, t in f.items() if t == latest)
        skew = latest - fastest
        total_skew += skew
        slow_wins[slowest] += 1
        for h, t in f.items():
            lag[h] += t - fastest
        supersteps.append({"step_first": sf, "hosts": len(f),
                           "skew_s": round(skew, 6),
                           "slowest_host": slowest})
    slowest_host = max(hosts, key=lambda h: (lag[h], -h)) \
        if total_skew > 0 else None

    coll_per_host = {str(h): per_host[str(h)]["collective_s"] for h in hosts}
    coll_vals = [v for v in coll_per_host.values() if v]
    coll_mean = sum(coll_vals) / len(coll_vals) if coll_vals else 0.0
    vis_vals = [per_host[str(h)]["collective_visible_s"] for h in hosts
                if per_host[str(h)]["collective_s"]]
    vis_mean = sum(vis_vals) / len(vis_vals) if vis_vals else 0.0

    straggler_s = round(total_skew, 6)
    collective_s = round(coll_mean, 6)
    # The verdict charges only the VISIBLE collective share: seconds a
    # window-boundary partial merge spent overlapped with busy map lanes
    # are already paid for, and switching strategy cannot win them back.
    visible_s = round(vis_mean, 6)
    hidden_s = round(collective_s - visible_s, 6)
    if span > 0 and straggler_s >= visible_s \
            and straggler_s / span > FLEET_MIN_FRAC:
        # Saving capped at the span: per-superstep skews are summed, and
        # a consistently slow host can accumulate more lag-seconds than
        # the concurrent wall-clock they could ever give back.
        verdict, saving = "straggler-bound", min(straggler_s, span)
        detail = (f"host skew costs {straggler_s:.3f}s of the "
                  f"{span:.3f}s fleet span "
                  f"({100 * straggler_s / span:.0f}%): host "
                  f"{slowest_host} ran latest on "
                  f"{slow_wins.get(slowest_host, 0)}/{len(supersteps)} "
                  "supersteps — a perfectly balanced fleet saves "
                  f"~{straggler_s:.3f}s; rebalance the data before "
                  "touching collective strategy")
    elif span > 0 and visible_s > straggler_s \
            and visible_s / span > FLEET_MIN_FRAC:
        verdict, saving = "collective-bound", visible_s
        detail = (f"the collective finish costs {visible_s:.3f}s of "
                  f"the {span:.3f}s fleet span "
                  f"({100 * visible_s / span:.0f}%), more than the "
                  f"{straggler_s:.3f}s host skew — the reduction "
                  "strategy/schedule is the lever (ROADMAP item 3)")
        if hidden_s > 0:
            detail += (f" (a further {hidden_s:.3f}s of collective time "
                       "already hides inside the map stream)")
    else:
        verdict, saving = "balanced", max(straggler_s, visible_s)
        detail = (f"neither host skew ({straggler_s:.3f}s) nor the "
                  f"visible collective finish ({visible_s:.3f}s) clears "
                  f"{FLEET_MIN_FRAC:.0%} of the {span:.3f}s fleet span")
        if hidden_s > 0:
            detail += (f" — window-boundary overlap hides {hidden_s:.3f}s "
                       f"of the {collective_s:.3f}s total collective time "
                       "inside the map stream")

    imbalance_counters = {
        h: {k: v for k, v in (("bytes", per_host[str(h)]["host_bytes"]),
                              ("tokens", per_host[str(h)]["tokens"]))
            if v is not None}
        for h in hosts}
    imbalance = datahealth.classify_fleet(imbalance_counters)

    processes = next((r.get("processes") for _, recs in sel.values()
                      for r in recs if r.get("kind") == "run_start"
                      and _num(r.get("processes")) is not None), None)
    return {
        "hosts": hosts,
        "processes": processes,
        "aligned": aligned,
        "run_ids": {str(h): sel[h][0] for h in hosts},
        "t0": round(t0, 6),
        "span_s": round(span, 6),
        "per_host": per_host,
        "supersteps": supersteps,
        "straggler": {
            "total_skew_s": straggler_s,
            "supersteps": len(supersteps),
            "slowest_host": slowest_host,
            "slowest_wins": slow_wins.get(slowest_host, 0)
            if slowest_host is not None else 0,
            "per_host_lag_s": {str(h): round(lag[h], 6) for h in hosts},
        },
        "collective": {"mean_s": collective_s,
                       "visible_mean_s": visible_s,
                       "hidden_mean_s": hidden_s,
                       "per_host_s": coll_per_host},
        "fleet_bottleneck": {
            "verdict": verdict,
            "projected_saving_s": round(saving, 6),
            "straggler_s": straggler_s,
            "collective_s": collective_s,
            "collective_visible_s": visible_s,
            "collective_hidden_s": hidden_s,
            "span_s": round(span, 6),
            "detail": detail,
        },
        "imbalance": imbalance,
    }


def fleet_record(view: dict) -> dict:
    """The synthesized ``fleet`` ledger record a merged file carries —
    what ``tuning.derive_signals`` reads ``fleet_bottleneck`` from."""
    hosts = view["hosts"]
    return {"kind": "fleet",
            "run_id": view["run_ids"].get(str(hosts[0])) if hosts else None,
            "hosts": hosts,
            "fleet_bottleneck": view["fleet_bottleneck"],
            "straggler": view["straggler"],
            "imbalance": view["imbalance"]}


def merged_records(by_host: Dict[int, List[dict]],
                   run_id: Optional[str] = None, *,
                   selected=None, view=None) -> List[dict]:
    """The deterministic merged record stream: every shard's selected run
    (clock-aligned), concatenated in host order, plus the ``fleet``
    record last.  Two invocations over the same shards produce identical
    bytes when serialized line-by-line (the byte-stability contract).
    ``selected``/``view`` reuse already-computed selection/artifact."""
    selected = selected if selected is not None \
        else _select_aligned(by_host, run_id)
    sel, _ = selected
    out: List[dict] = []
    for h in sorted(sel):
        out.extend(sel[h][1])
    if view is None:
        view = fleet_view(by_host, run_id, selected=selected)
    if view is not None:
        out.append(fleet_record(view))
    return out


# -- Chrome trace rendering (one pid per host) -------------------------------

def to_chrome_trace(by_host: Dict[int, List[dict]],
                    run_id: Optional[str] = None, *,
                    selected=None, view=None) -> Optional[dict]:
    """Shard records -> Chrome trace-event JSON: one **pid per host**
    (``host <h>``), one **tid per resource lane** inside it (reader /
    staging / h2d / device / retire / collective), complete slices per
    group lifecycle interval on the shared fleet clock.  The
    ``otherData.fleet_bottleneck`` dict carries the verdict.
    ``selected``/``view`` reuse already-computed selection/artifact."""
    selected = selected if selected is not None \
        else _select_aligned(by_host, run_id)
    if view is None:
        view = fleet_view(by_host, run_id, selected=selected)
    if view is None:
        return None
    sel, _ = selected
    t0 = view["t0"]
    tid = {lane: i for i, lane in enumerate(timeline.FLEET_LANES)}
    events: List[dict] = []
    named_threads = set()
    for idx, h in enumerate(sorted(sel)):
        pid = idx + 1
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "args": {"name": f"host {h}"}})
        events.append({"ph": "M", "name": "process_sort_index", "pid": pid,
                       "args": {"sort_index": pid}})
        rid, recs = sel[h]
        for lane, s, e, rec in _intervals(recs, rid):
            if (pid, tid[lane]) not in named_threads:
                named_threads.add((pid, tid[lane]))
                events.append({"ph": "M", "name": "thread_name", "pid": pid,
                               "tid": tid[lane], "args": {"name": lane}})
            if lane == "collective":
                name = f"collective {rec.get('op', 'finish')}"
                args = {k: rec.get(k) for k in ("op", "strategy")
                        if rec.get(k) is not None}
            else:
                label = (f"g{rec.get('step_first', '?')}-"
                         f"{rec.get('step_last', '?')}")
                name = f"{timeline._SLICE[lane]} {label}"
                args = {k: rec.get(k) for k in
                        ("step_first", "step_last", "steps", "group_bytes",
                         "host_bytes", "retries", "retire_wait_s")
                        if rec.get(k) is not None}
            events.append({"ph": "X", "cat": "lane", "name": name,
                           "pid": pid, "tid": tid[lane],
                           "ts": round((s - t0) * 1e6, 3),
                           "dur": round((e - s) * 1e6, 3), "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"hosts": view["hosts"],
                          "run_ids": view["run_ids"],
                          "fleet_bottleneck": view["fleet_bottleneck"],
                          "imbalance": view["imbalance"]}}


# -- discovery + rendering ---------------------------------------------------

def from_ledger(ledger_path: str,
                run_id: Optional[str] = None) -> Optional[dict]:
    """Convenience: discover ``<ledger>.h*.jsonl`` shards next to a main
    ledger and build the fleet view — None when no shards exist (the
    single-host case ``obs_report`` degrades on)."""
    paths = shard_paths(ledger_path)
    if not paths:
        return None
    return fleet_view({h: read_jsonl(p) for h, p in paths.items()}, run_id)


def render(view: dict, out) -> None:
    hosts = ", ".join(f"h{h}" for h in view["hosts"])
    out.write(f"fleet: {len(view['hosts'])} hosts ({hosts}), "
              f"span {view['span_s']:.3f}s, "
              f"{'aligned' if view['aligned'] else 'UNALIGNED'} clocks\n")
    for h in view["hosts"]:
        p = view["per_host"][str(h)]
        out.write(f"  h{h}: {p['groups']} groups, device busy "
                  f"{p['device_busy_s']:.3f}s, collective "
                  f"{p['collective_s']:.3f}s")
        if p.get("collective_hidden_s"):
            out.write(f" ({p['collective_hidden_s']:.3f}s overlapped)")
        if p.get("host_bytes") is not None:
            out.write(f", host bytes {p['host_bytes']}")
        if p.get("bottleneck"):
            out.write(f", bottleneck {p['bottleneck']}")
        out.write("\n")
    st = view["straggler"]
    if st["supersteps"]:
        out.write(f"  straggler: total skew {st['total_skew_s']:.3f}s "
                  f"across {st['supersteps']} supersteps; slowest host "
                  f"{st['slowest_host']} "
                  f"({st['slowest_wins']}/{st['supersteps']})\n")
    out.write(f"  collective: mean {view['collective']['mean_s']:.3f}s")
    if view["collective"].get("hidden_mean_s"):
        out.write(f" ({view['collective']['hidden_mean_s']:.3f}s hidden "
                  "by window-boundary overlap)")
    out.write("\n")
    bn = view["fleet_bottleneck"]
    out.write(f"  fleet bottleneck: {bn['verdict']} — {bn['detail']}\n")
    imb = view["imbalance"]
    for f in imb.get("flags", []):
        out.write(f"  FLEET {f['flag']}: {f['detail']}\n")


# -- selftest ----------------------------------------------------------------

def _fixture_dir() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, os.pardir, "tools", "fixtures")


def selftest() -> int:
    """Merge the checked-in two-host shard fixtures and assert the
    hand-computed skew/verdict arithmetic, merge determinism, alignment,
    the synthesized collective-bound/balanced cases, and forward compat."""
    fdir = _fixture_dir()
    main_path = os.path.join(fdir, "fleet_ledger.jsonl")
    by_host = {h: read_jsonl(p) for h, p in shard_paths(main_path).items()}
    assert sorted(by_host) == [0, 1], f"two shard fixtures expected: {by_host.keys()}"

    view = fleet_view(by_host)
    assert view is not None and view["hosts"] == [0, 1], view
    assert view["aligned"] is True and view["processes"] == 2, view
    # Hand arithmetic (offsets: h0 wall 1000 - mono 100 = +900, h1 +500):
    # finishes h0 = 1001.0/1002.0/1003.0, h1 = 1001.5/1002.8/1003.7 ->
    # skews 0.5/0.8/0.7, total 2.0; h1 latest on all 3 supersteps.
    st = view["straggler"]
    assert [s["skew_s"] for s in view["supersteps"]] == [0.5, 0.8, 0.7], \
        view["supersteps"]
    assert st["total_skew_s"] == 2.0 and st["slowest_host"] == 1, st
    assert st["slowest_wins"] == 3 and st["per_host_lag_s"]["0"] == 0.0, st
    # Span: earliest read 1000.0 -> latest collective end 1004.05.
    assert view["span_s"] == 4.05, view["span_s"]
    # Collective: 0.3 s on each host, mean 0.3.
    assert view["collective"]["mean_s"] == 0.3, view["collective"]
    assert view["per_host"]["0"]["collective_s"] == 0.3
    # Device busy: h0 3x0.85 = 2.55, h1 1.3+1.15+0.75 = 3.2.
    assert view["per_host"]["0"]["device_busy_s"] == 2.55, view["per_host"]
    assert view["per_host"]["1"]["device_busy_s"] == 3.2, view["per_host"]
    # Verdict: 2.0 s skew >= 0.3 s collective and 49% of the 4.05 s span.
    bn = view["fleet_bottleneck"]
    assert bn["verdict"] == "straggler-bound", bn
    assert bn["projected_saving_s"] == 2.0, bn
    assert "host 1 ran latest on 3/3" in bn["detail"], bn
    # Imbalance: host_bytes 12288 vs 24576 -> ratio 24576/18432 = 1.333;
    # tokens 3000 vs 6000 -> same ratio.  Both clear the 1.25 gate.
    imb = view["imbalance"]
    assert imb["verdict"] == "host-imbalance", imb
    assert imb["signals"]["bytes_ratio"] == round(24576 / 18432, 6), imb
    assert imb["signals"]["tokens_hot_host"] == 1, imb

    # Merge determinism: two invocations -> byte-identical artifacts AND
    # byte-identical merged record streams.
    a = json.dumps(fleet_view(by_host), sort_keys=True)
    b = json.dumps(fleet_view(
        {h: read_jsonl(p) for h, p in shard_paths(main_path).items()}),
        sort_keys=True)
    assert a == b, "fleet view must be byte-stable across merges"
    ma = "\n".join(json.dumps(r, sort_keys=True)
                   for r in merged_records(by_host))
    mb = "\n".join(json.dumps(r, sort_keys=True)
                   for r in merged_records(by_host))
    assert ma == mb and '"kind": "fleet"' in ma, \
        "merged stream must be byte-stable and carry the fleet record"

    # The fleet trace: one pid per host, lanes as tids, schema basics.
    trace = to_chrome_trace(by_host)
    pnames = {e["pid"]: e["args"]["name"] for e in trace["traceEvents"]
              if e["ph"] == "M" and e["name"] == "process_name"}
    assert sorted(pnames.values()) == ["host 0", "host 1"], pnames
    slices = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in slices), slices
    assert any(e["name"].startswith("collective") for e in slices), \
        "the collective lane must render"
    assert trace["otherData"]["fleet_bottleneck"]["verdict"] \
        == "straggler-bound"
    assert json.loads(json.dumps(trace)) == trace

    # Synthesized collective-bound case: negligible skew, fat finish.
    def g(h, sf, disp, ready):
        return {"run_id": "c", "kind": "group", "host": h, "step_first": sf,
                "step_last": sf, "group_bytes": 64, "staged_at": disp - 0.01,
                "dispatched_at": disp, "token_ready_at": ready,
                "retired_at": ready + 0.01}

    def rs(h):
        return {"run_id": "c", "kind": "run_start", "host": h,
                "processes": 2, "clock": {"wall": 50.0, "mono": 0.0}}

    coll = {0: [rs(0), g(0, 0, 1.0, 2.0),
                {"run_id": "c", "kind": "collective", "op": "finish",
                 "strategy": "tree", "started_at": 2.1, "ended_at": 3.6}],
            1: [rs(1), g(1, 0, 1.0, 2.01),
                {"run_id": "c", "kind": "collective", "op": "finish",
                 "strategy": "tree", "started_at": 2.1, "ended_at": 3.6}]}
    cview = fleet_view(coll)
    cbn = cview["fleet_bottleneck"]
    assert cbn["verdict"] == "collective-bound", cbn
    assert cbn["projected_saving_s"] == 1.5, cbn  # the 1.5 s finish
    assert cview["imbalance"]["verdict"] == "balanced", cview["imbalance"]

    # Overlap accounting (ISSUE 20): the same amount of collective time,
    # but shipped as a window-boundary partial merge that rides INSIDE
    # the map stream — the hidden share charges nothing and the verdict
    # flips to balanced.  Hand arithmetic: device lane 1.0-4.0, partial
    # 1.5-2.8 fully inside it (hidden 1.3), finish 4.05-4.25 exclusive
    # (visible 0.2); span 0.99-4.26 = 3.27, visible 0.2/3.27 = 6% < 10%.
    def co(op, s, e):
        return {"run_id": "o", "kind": "collective", "op": op,
                "strategy": "tree", "step": 0,
                "started_at": s, "ended_at": e}

    def rso(h):
        return {"run_id": "o", "kind": "run_start", "host": h,
                "processes": 2, "clock": {"wall": 50.0, "mono": 0.0}}

    def go(h):
        return {"run_id": "o", "kind": "group", "host": h, "step_first": 0,
                "step_last": 0, "group_bytes": 64, "staged_at": 0.99,
                "dispatched_at": 1.0, "token_ready_at": 4.0 + 0.01 * h,
                "retired_at": 4.01 + 0.01 * h}

    ov = {h: [rso(h), go(h), co("partial", 1.5, 2.8),
              co("finish", 4.05, 4.25)] for h in (0, 1)}
    oview = fleet_view(ov)
    oph = oview["per_host"]["0"]
    assert oph["collective_s"] == 1.5 and oph["collective_hidden_s"] == 1.3 \
        and oph["collective_visible_s"] == 0.2, oph
    assert oview["collective"]["visible_mean_s"] == 0.2 \
        and oview["collective"]["hidden_mean_s"] == 1.3, oview["collective"]
    obn = oview["fleet_bottleneck"]
    assert obn["verdict"] == "balanced", obn
    assert obn["collective_s"] == 1.5 and obn["collective_visible_s"] == 0.2, obn
    assert "overlap hides 1.300s" in obn["detail"], obn
    # The exclusive twin: the SAME 1.5 s of collective time, but the
    # partial fires after the map lanes drain -> all visible, and the
    # old collective-bound verdict comes back.
    ex = {h: [rso(h), go(h), co("partial", 4.3, 5.6),
              co("finish", 4.05, 4.25)] for h in (0, 1)}
    eview = fleet_view(ex)
    ebn = eview["fleet_bottleneck"]
    assert ebn["verdict"] == "collective-bound", ebn
    assert ebn["collective_hidden_s"] == 0.0 \
        and ebn["collective_visible_s"] == 1.5, ebn

    # Balanced: equal hosts, thin collective -> nothing clears 10%.
    bal = {0: [rs(0), g(0, 0, 1.0, 2.0)], 1: [rs(1), g(1, 0, 1.0, 2.0)]}
    bview = fleet_view(bal)
    assert bview["fleet_bottleneck"]["verdict"] == "balanced", bview
    assert bview["straggler"]["total_skew_s"] == 0.0

    # Unaligned degrade: strip one clock -> raw monotonic stamps, flagged.
    unal = {h: [dict(r) for r in recs] for h, recs in bal.items()}
    for r in unal[1]:
        r.pop("clock", None)
    uview = fleet_view(unal)
    assert uview is not None and uview["aligned"] is False, uview

    # Forward compat: the future-versioned fixture merges as one shard
    # (unknown kinds/fields skipped or carried, never an error).
    fut = os.path.join(fdir, "future_ledger.jsonl")
    fview = fleet_view(load_shards([fut]))
    assert fview is not None and fview["hosts"] == [0], fview
    assert fview["fleet_bottleneck"]["verdict"] in (
        "balanced", "collective-bound", "straggler-bound"), fview

    print("fleet selftest ok (2 hosts, skew "
          f"{st['total_skew_s']}s over {st['supersteps']} supersteps, "
          f"verdict {bn['verdict']}, imbalance {imb['verdict']}, "
          f"{len(slices)} trace slices, byte-stable merge, "
          "collective-bound/overlap-hidden/balanced/unaligned/future "
          "cases ok)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-host mapreduce_tpu ledger shards into a "
                    "fleet timeline + straggler/collective verdict")
    ap.add_argument("ledgers", nargs="*",
                    help="main ledger path (shards discovered as "
                         "<ledger>.h*.jsonl) or explicit shard paths")
    ap.add_argument("--run-id", default=None,
                    help="run to merge (default: each shard's last run)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable fleet artifact")
    ap.add_argument("--trace", default=None, metavar="OUT",
                    help="also write the pid-per-host Chrome trace JSON")
    ap.add_argument("--merged", default=None, metavar="OUT",
                    help="also write the merged record stream (+ fleet "
                         "record) as JSONL")
    ap.add_argument("--selftest", action="store_true",
                    help="run against the checked-in fixtures and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.ledgers:
        ap.error("a ledger path (or --selftest) is required")
    if len(args.ledgers) == 1 and not _SHARD_RE.search(args.ledgers[0]):
        paths = shard_paths(args.ledgers[0])
        if not paths:
            print(f"no shard files ({args.ledgers[0]}.h*.jsonl) found — "
                  "not a multi-host ledger?", file=sys.stderr)
            return 1
        by_host = {h: read_jsonl(p) for h, p in paths.items()}
    else:
        by_host = load_shards(args.ledgers)
    selected = _select_aligned(by_host, args.run_id)
    view = fleet_view(by_host, args.run_id, selected=selected)
    if view is None:
        print("no usable records in the shards", file=sys.stderr)
        return 1
    if args.merged:
        with open(args.merged, "w", encoding="utf-8") as f:
            for r in merged_records(by_host, args.run_id,
                                    selected=selected, view=view):
                f.write(json.dumps(r, sort_keys=True) + "\n")
    if args.trace:
        trace = to_chrome_trace(by_host, args.run_id,
                                selected=selected, view=view)
        with open(args.trace, "w", encoding="utf-8") as f:
            json.dump(trace, f)
    if args.json:
        print(json.dumps(view, sort_keys=True))
    else:
        render(view, sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
