"""Per-run JSONL run ledger (ISSUE 2 tentpole (2)).

One machine-readable record per streaming step/superstep, plus run start/end
and checkpoint/retry/failure markers — the durable trace that makes a run's
seconds attributable after the fact (the 3x streamed-vs-H2D gap of VERDICT
r4 was unattributable until phase timers were threaded in by hand; the
ledger records what those timers see, every run).

Format: one JSON object per line, append-only, flushed per record so a
crashed or wedged run keeps every record up to the wedge.  In multi-host
runs only the checkpoint-writing process (the coordinator) writes —
callers gate on the executor's ``write_gate`` hook.

Record kinds (full schema: docs/observability.md):

=============  ===========================================================
kind           carries
=============  ===========================================================
run_start      run_id, ledger_version, config summary (devices,
               chunk_bytes, superstep, backend, map_impl, input paths),
               resume cursor
step           step_first/step_last/steps, group_bytes, cursor_bytes,
               per-phase second deltas (read_wait/stage/dispatch/...),
               elapsed_s since the previous record, device memory stats,
               compile events landed since the previous record, retries
group          one per RETIRED superstep group (ISSUE 7): monotonic-clock
               lifecycle timestamps (read_at/staged_at/dispatched_at/
               token_ready_at/retired_at, h2d_done_at on the last group),
               group bytes/steps, retire_wait_s, retry attempts — the raw
               material ``obs/timeline.py`` reconstructs per-resource
               timelines, overlap matrices and critical-path verdicts
               from — plus the group's ``data`` dict (ISSUE 8: per-group
               overlong/rescued/dropped/spill-fallback counters and
               running occupancy/top-mass) on stats-mode runs
data           one per run (ISSUE 8, before run_end): the data-plane
               summary — overlong/rescued/dropped totals, spill-fallback
               and rescue-escalation counts, table occupancy, top-bucket
               mass (key-skew proxy), stable2 window occupancy —
               classified by ``obs/datahealth.py`` and consumed by the
               window autotuner next to the timeline verdict
tune           one per run on ``Config(autotune='hint')`` runs (ISSUE 10,
               before run_end): the autotuner's recommendation — current
               vs proposed inflight_groups/prefetch_depth/superstep/
               chunk_bytes, the fired rule + reason, the signals read
               (bottleneck resource, projected-saving fraction, data
               verdict, window stats), and the full rule-by-rule decision
               trail.  Advisory: the live run is never changed
collective     the collective reduction's monotonic interval
               (started_at/ended_at) + merge strategy + ``op`` (ledger
               v10): ``op="finish"`` is the end-of-stream global reduce
               (ISSUE 13, one per run, inside the reduce phase);
               ``op="partial"`` is a window-boundary overlap merge
               (ISSUE 20, ``Config.merge_overlap`` runs only: one per
               retired partial, stamped with the boundary ``step``) —
               together the raw material of the fleet timeline's
               ``collective`` lane (strategy *builds* stay registry
               metrics: they happen at trace time)
progress       the live-run heartbeat (ISSUE 14, ledger v8): emitted on
               a wall-clock cadence from the dispatch/retire points —
               stream cursor + total bytes + completion fraction,
               groups dispatched/retired, current in-flight depth,
               throughput-so-far, ETA from the byte cursor.  Host-side
               only (no device work, no memory-stat sampling) and
               flushed per record, so ``tools/obswatch.py`` can tail a
               run in flight
checkpoint     step, cursor_bytes, save_s, path
retry          step, attempt, error
failure        step, cursor_bytes, error, flight-dump path (if written)
run_end        RunMetrics summary (bytes, words, elapsed, phases, GB/s)
=============  ===========================================================

Multi-host (ISSUE 13, ledger v7): every process of a multi-host run
writes its OWN shard file ``<ledger>.h<process_index>.jsonl`` (see
:func:`shard_path`) carrying every record kind above stamped with the
process's ``host`` index; ``run_start`` additionally carries the
process/device topology (``processes``, ``local_devices``) and the
``clock`` pair ``{wall, mono}`` sampled at ``jax.distributed`` init, so
``obs/fleet.py`` can rebase each host's monotonic lifecycle stamps onto
the shared wall clock and merge the shards into one fleet timeline.  The
coordinator keeps writing the merged-authoritative main file exactly as
before; flight dumps land per host (:func:`shard_flight_path` on
non-coordinators), so a remote failure leaves forensics from the host
that actually failed instead of being swallowed by the write gate.

Forward compatibility (ISSUE 7 satellite): ``run_start`` records carry
``ledger_version``; every consumer (:func:`read_ledger`, ``obs_report``,
``timeline``, ``trace_export``) skips unknown record kinds and unknown
fields instead of erroring, so a ledger written by a NEWER version of this
code still renders on an older reader — and vice versa.

Readers: :func:`read_ledger` here (used by tests) and ``tools/obs_report.py``
(the human/anomaly report; deliberately jax-free so it runs anywhere).
"""

from __future__ import annotations

import json
import os
import time
from typing import Iterator, Optional

#: Bumped when the record stream gains kinds/fields a consumer may care to
#: version-gate on.  1 = ISSUE 2-6 shape (implicit; pre-ISSUE-7 ledgers
#: carry no version field at all); 2 = adds ``group`` lifecycle records;
#: 3 = adds the per-run ``data`` record + per-group ``data`` dicts
#: (ISSUE 8); 4 = adds the per-run ``tune`` record (ISSUE 10: the window
#: autotuner's recommendation + decision trail, ``autotune='hint'`` runs
#: only); 5 = the ``data`` record and run_start gain the map-side
#: combiner fields (ISSUE 11: ``combiner`` resolved mode,
#: ``combiner_hits``/``combiner_flushes``/``combiner_evicted`` counters,
#: ``combiner_hit_rate``/``combiner_rows_deleted`` derived ratios);
#: 6 = run_start gains the kernel-geometry stamp (ISSUE 12: ``geometry``
#: label — 'default', a preset name, or 'custom' — plus
#: ``geometry_spec`` with the full field dict on custom runs), the knob
#: the geometry search tunes and ``obs_report --compare`` diffs;
#: 7 = pod-scale observability (ISSUE 13): multi-host records carry the
#: ``host`` process-index stamp, run_start the ``processes``/
#: ``local_devices`` topology + the ``clock`` {wall, mono} alignment
#: pair, every process writes its own ``<ledger>.h<p>.jsonl`` shard, and
#: the new per-run ``collective`` record times the collective finish;
#: 8 = live run watching (ISSUE 14): the executor's telemetry emits a
#: periodic ``progress`` heartbeat record (wall-clock cadence, host-side
#: only: stream cursor + total bytes, groups dispatched/retired, current
#: in-flight depth, throughput-so-far and the ETA derived from the byte
#: cursor), flushed like every record so ``tools/obswatch.py`` can tail
#: a run that has not ended — and ``obs/history.py`` can digest crashed
#: runs up to their last heartbeat;
#: 9 = robustness (ISSUE 15): typed ``fault`` records (seam,
#: fault_class, injected, crossing index — a chaotic run's own replayable
#: schedule via ``runtime/faults.FaultPlan.from_ledger``), ``degrade``
#: records (one per degradation-ladder step: ladder_step, field,
#: from/to), ``retry``/``failure`` records gain ``fault_class`` (+
#: ``seam`` on non-dispatch retries), and run_start stamps the
#: ``fault_plan`` spec on chaos runs.  Fault-free runs emit no new
#: records and no new fields beyond the version stamp;
#: 10 = placed reductions at runtime (ISSUE 20): ``collective`` records
#: gain ``op`` ("finish" = the end-of-stream reduce, exactly the v7
#: record; "partial" = a window-boundary overlap merge, one per retired
#: partial with its boundary ``step``), run_start stamps
#: ``merge_overlap: true`` on overlapped runs (absent otherwise), and
#: ``merge_strategy`` may now name a hierarchical 2-D program
#: (``hier-kr-tree`` / ``hier-tree-tree``).  Overlap-off runs emit no
#: new records and no new fields beyond the version stamp and the
#: finish record's ``op`` tag.
LEDGER_VERSION = 10


def shard_path(path: str, process_index: int) -> str:
    """The per-host shard ledger next to the main file (ledger v7):
    ``run.jsonl`` -> ``run.jsonl.h3.jsonl`` for process 3."""
    return f"{path}.h{int(process_index)}.jsonl"


def shard_flight_path(path: str, process_index: int) -> str:
    """The host-suffixed flight-dump path (ISSUE 13 bugfix: a
    non-coordinator failure dumps HERE instead of being swallowed by the
    coordinator-only write gate)."""
    return f"{path}.h{int(process_index)}.flight.json"


class RunLedger:
    """Append-only JSONL writer.  Not thread-safe by design: the executor
    writes from the driving thread only (the prefetch thread records into
    the metrics registry instead)."""

    def __init__(self, path: str, run_id: str):
        self.path = path
        self.run_id = run_id
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self.records_written = 0

    def write(self, kind: str, **fields) -> None:
        if kind == "run_start":
            # Every writer stamps the stream's schema version exactly once,
            # without each call site having to remember to.
            fields.setdefault("ledger_version", LEDGER_VERSION)
        rec = {"ts": round(time.time(), 6), "run_id": self.run_id,
               "kind": kind, **fields}
        self._f.write(json.dumps(rec, default=_json_default) + "\n")
        self._f.flush()  # a wedged run must keep everything up to the wedge
        self.records_written += 1

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self) -> "RunLedger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _json_default(obj):
    """Last-resort coercion: numpy scalars AND arrays ride through cleanly
    (``tolist`` handles both — ``item()`` would raise on size > 1);
    anything else becomes its repr (a ledger write must never take down
    the run it is observing)."""
    if hasattr(obj, "tolist"):
        try:
            return obj.tolist()
        except Exception:
            pass
    return repr(obj)


def read_ledger(path: str, kind: Optional[str] = None) -> Iterator[dict]:
    """Yield ledger records, skipping lines that fail to parse (a record
    truncated by a crash mid-write is expected forensics, not an error)."""
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if kind is None or rec.get("kind") == kind:
                yield rec
