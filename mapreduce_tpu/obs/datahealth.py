"""Data-health classifier (ISSUE 8): the per-run ``data`` ledger record
-> a machine-readable verdict on what the DATA did to the run.

PR 7's ``bottleneck`` verdict names the pipeline resource that bounded a
run (reader/staging/h2d/device/retire); this module names the *data
shape* that bounded the device side — the fitness signals the window/
config autotuner (ROADMAP item 1) consumes, and what "Synthesizing
Optimal Parallelism Placement and Reduction Strategies" (PAPERS.md) makes
reduction-strategy choice a function of (the key distribution):

==================  =======================================================
verdict             meaning (and the knob it points at)
==================  =======================================================
spill-bound         compact/fused kernel windows overflowed their slot
                    budget and chunks re-ran at full resolution — each
                    fallback ~doubles that chunk's map cost (raise
                    ``--compact-slots``, or the corpus is adversarially
                    dense)
rescue-heavy        overlong (>W-byte) tokens are a measurable share of
                    the stream, or tier-2 rescue escalations fired
                    (URL/markup-dense text: raise ``--max-token-bytes`` /
                    the rescue budgets, or accept the accounting)
skew-hot            one key carries a double-digit share of all tokens
                    (Zipf-hot): merges and top-k are cheap, but key-range
                    partitioning would load-imbalance — prefer tree merge
                    and expect sort runs to be long
occupancy-starved   the compact kernel windows ran mostly empty — the
                    sorted stream is mostly padding (shrink
                    ``--compact-slots`` or grow chunk size)
table-pressure      the running table is near capacity or actively
                    dropping keys (raise ``--table-capacity`` or accept
                    the KMV estimate)
clean               none of the above fired
==================  =======================================================

Multiple flags can fire; ``verdict`` is the highest-priority one in the
table order above (the order is cost impact: a spill fallback doubles map
work, starved windows only waste sort rows).  Every flag carries its
measured signal, so the autotuner reads numbers, not adjectives.

Deliberately jax-free and stdlib-only (the ``obs/timeline.py`` contract):
``tools/obs_report.py`` loads this module by file path on boxes with
neither jax nor the package installed.
"""

from __future__ import annotations

from typing import Iterable, Optional

#: Share of chunks taking the full-resolution fallback that makes a run
#: spill-bound (each one ~doubles that chunk's map cost).
SPILL_FALLBACK_FRAC = 0.05
#: Overlong occurrences as a share of all tokens that makes a run
#: rescue-heavy (natural text measures ~0; webby text ~5e-4/chunk budget).
OVERLONG_FRAC = 1e-3
#: Top single-key mass that makes a corpus skew-hot.  Zipf-ish natural
#: text puts >5% of all tokens on the top key ("the"); a uniform corpus
#: puts ~1/distinct there.
TOP_MASS_HOT = 0.05
#: Compact-window slot occupancy below which the sort input is mostly
#: padding (the stable2 windows carry `slots` rows whether used or not).
WINDOW_OCCUPANCY_FLOOR = 0.25
#: Running-table occupancy that signals imminent key spill.
TABLE_OCCUPANCY_CEIL = 0.9


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def _frac(num, den) -> Optional[float]:
    n, d = _num(num), _num(den)
    if n is None or not d:
        return None
    return n / d


def classify(data: dict) -> dict:
    """One run's ``data`` record -> ``{verdict, flags, signals}``.

    ``signals`` carries every derived ratio (present or None — absence of
    a signal is itself information: an xla-backend run has no windows to
    starve); each entry of ``flags`` carries the measured number that
    fired it.  Unknown/extra fields in ``data`` are ignored (ledger
    forward compat)."""
    chunks = _num(data.get("chunks")) or 0.0
    tokens = _num(data.get("tokens")) or 0.0
    signals = {
        "fallback_frac": _frac(data.get("fallback_chunks", 0), chunks),
        "overlong_frac": _frac(data.get("overlong", 0), tokens),
        "rescued_frac": _frac(data.get("rescued", 0),
                              data.get("overlong", 0)),
        "dropped_frac": _frac(data.get("dropped_tokens", 0), tokens),
        "top_mass": _frac(data.get("top_count", 0), tokens),
        "distinct_ratio": _frac(data.get("table_valid", 0), tokens),
        "table_occupancy": _frac(data.get("table_valid", 0),
                                 data.get("capacity", 0)),
        "window_occupancy": _num(data.get("window_occupancy")),
        "rescue_escalations": _num(data.get("rescue_escalations", 0)),
        # Map-side combiner telemetry (ISSUE 11): share of all tokens the
        # hot-key cache absorbed, and the net sort rows it deleted.  Pure
        # observability — no flag fires on them (the combiner is the CURE
        # for skew-hot, not a symptom), but the skew-hot detail below
        # points at the knob and the autotuner's enable-combiner rule
        # reads the verdict.
        "combiner_hit_rate": _frac(data.get("combiner_hits", 0),
                                   data.get("tokens", 0)),
        "combiner_rows_deleted": _num(data.get("combiner_rows_deleted")),
    }
    signals = {k: (round(v, 6) if v is not None else None)
               for k, v in signals.items()}
    flags = []

    def flag(name: str, detail: str) -> None:
        flags.append({"flag": name, "detail": detail})

    ff = signals["fallback_frac"]
    if ff is not None and ff > SPILL_FALLBACK_FRAC:
        flag("spill-bound",
             f"{ff:.1%} of chunks overflowed their compact window slots "
             f"and re-ran at full resolution (spill_rows="
             f"{data.get('spill_rows', 0)}) — each fallback ~doubles that "
             "chunk's map cost; raise --compact-slots or accept the 2x")
    of = signals["overlong_frac"]
    esc = signals["rescue_escalations"] or 0
    if (of is not None and of > OVERLONG_FRAC) or esc > 0:
        rf = signals["rescued_frac"]
        rescued_part = f", rescued {rf:.0%} of them" if rf is not None else ""
        flag("rescue-heavy",
             f"overlong tokens are {(of or 0):.2%} of the stream with "
             f"{int(esc)} tier-2 escalations{rescued_part} — raise "
             "--max-token-bytes / the rescue budgets for URL-dense text")
    tm = signals["top_mass"]
    if tm is not None and tm > TOP_MASS_HOT:
        ch = signals["combiner_hit_rate"]
        cure = (f"the map-side combiner is absorbing {ch:.1%} of the "
                "stream" if ch else
                "enable the map-side combiner (--combiner hot-cache, or "
                "'auto' to let this verdict decide)")
        flag("skew-hot",
             f"the hottest key carries {tm:.1%} of all tokens "
             f"(Zipf-hot): {cure}; key-range partitioning would "
             "load-imbalance — prefer tree merge")
    wo = signals["window_occupancy"]
    if wo is not None and wo < WINDOW_OCCUPANCY_FLOOR:
        flag("occupancy-starved",
             f"compact kernel windows ran {wo:.1%} full: the aggregation "
             "sort is mostly sorting padding — shrink --compact-slots or "
             "grow the chunk")
    to = signals["table_occupancy"]
    dropped_uniques = _num(data.get("dropped_uniques", 0)) or 0
    if (to is not None and to > TABLE_OCCUPANCY_CEIL) or dropped_uniques > 0:
        flag("table-pressure",
             f"running table {to if to is not None else 0:.0%} full, "
             f"{int(dropped_uniques)} distinct keys spilled — raise "
             "--table-capacity or rely on the KMV/HLL estimates")

    order = ["spill-bound", "rescue-heavy", "skew-hot",
             "occupancy-starved", "table-pressure"]
    fired = {f["flag"] for f in flags}
    verdict = next((v for v in order if v in fired), "clean")
    return {"verdict": verdict, "flags": flags, "signals": signals}


def data_record(records: Iterable[dict],
                run_id: Optional[str] = None) -> Optional[dict]:
    """The ``data`` record of one run (the first run carrying one when
    ``run_id`` is not given).  Unknown kinds/malformed rows skip — the
    ledger forward-compat contract."""
    chosen = run_id
    for rec in records:
        if not isinstance(rec, dict) or rec.get("kind") != "data":
            continue
        if chosen is None:
            chosen = rec.get("run_id")
        if rec.get("run_id") == chosen:
            return rec
    return None


def classify_run(records: Iterable[dict],
                 run_id: Optional[str] = None) -> Optional[dict]:
    """Ledger records -> the health artifact of one run, or None when the
    run carries no ``data`` record (pre-ISSUE-8 ledgers degrade to "no
    data-health section", never to an error)."""
    rec = data_record(records, run_id)
    return classify(rec) if rec is not None else None


def latest_data_record(records: Iterable[dict]) -> Optional[dict]:
    """The LAST ``data`` record in a (possibly append-mode, multi-run)
    ledger — the most recent completed measurement, which is what
    history-driven decisions should read (contrast :func:`data_record`,
    which serves per-run analysis and keys on the FIRST run)."""
    last = None
    for rec in records:
        if isinstance(rec, dict) and rec.get("kind") == "data":
            last = rec
    return last


#: Hottest-host share over the per-host mean that makes a fleet
#: host-imbalanced (ISSUE 13): a host carrying >1.25x the mean bytes or
#: tokens finishes proportionally late every superstep — the signal the
#: ROADMAP-item-3 reduction-strategy planner needs before choosing
#: keyrange vs tree vs hierarchical merges.
HOST_IMBALANCE_RATIO = 1.25


def classify_fleet(per_host: dict) -> dict:
    """Per-host data counters -> the cross-host balance verdict
    (ISSUE 13): ``{verdict, flags, signals}`` like :func:`classify`, over
    ``{host: {"bytes": ..., "tokens": ...}}`` (any subset of counters;
    ``obs/fleet.py`` builds the dict from each shard's ``host_bytes``
    group fields and ``data`` records).  A counter present on >= 2 hosts
    whose hottest host carries more than :data:`HOST_IMBALANCE_RATIO`
    times the per-host mean fires ``host-imbalance``; the verdict is
    ``host-imbalance`` or ``balanced``.  Unknown/extra fields ignored."""
    signals: dict = {}
    flags = []
    for counter in ("bytes", "tokens"):
        vals = {h: _num(v.get(counter)) for h, v in per_host.items()
                if isinstance(v, dict) and _num(v.get(counter)) is not None}
        if len(vals) < 2:
            continue
        mean = sum(vals.values()) / len(vals)
        if mean <= 0:
            continue
        hot = max(sorted(vals), key=lambda h: vals[h])
        ratio = vals[hot] / mean
        signals[f"{counter}_ratio"] = round(ratio, 6)
        signals[f"{counter}_hot_host"] = hot
        if ratio > HOST_IMBALANCE_RATIO:
            flags.append({"flag": "host-imbalance", "counter": counter,
                          "detail": (f"host {hot} carries {ratio:.2f}x the "
                                     f"per-host mean {counter} "
                                     f"({vals[hot]:.0f} vs {mean:.0f}): it "
                                     "finishes proportionally late every "
                                     "superstep — rebalance the key ranges "
                                     "or prefer a skew-tolerant merge "
                                     "strategy (ROADMAP item 3)")})
    verdict = "host-imbalance" if flags else "balanced"
    return {"verdict": verdict, "flags": flags, "signals": signals}


#: Reliability verdict priority (ISSUE 15): highest-severity wins, the
#: :func:`classify` rule-table discipline.  A `failed` run died; a
#: `preempted` run exited cleanly with a resumable cursor; a `degraded`
#: run finished on a stepped-down config (alive but slower — visible,
#: not mysterious); a `fault-prone` run absorbed real faults with
#: retries; a `chaos-tested` run absorbed only INJECTED faults (a chaos
#: certification run that stayed exact).
RELIABILITY_ORDER = ("failed", "preempted", "degraded", "fault-prone",
                     "chaos-tested", "clean")


def classify_reliability(records: Iterable[dict],
                         run_id: Optional[str] = None) -> dict:
    """One run's ledger records -> the reliability verdict (ISSUE 15,
    ledger v9): ``{verdict, flags, signals}`` over the run's ``fault`` /
    ``degrade`` / ``retry`` / ``failure`` records.  Unknown kinds and
    extra fields skip (forward compat); a pre-v9 ledger with none of
    these kinds reads ``clean`` — exactly what it observed."""
    chosen = run_id
    faults: list = []
    degrades: list = []
    retries_by_class: dict = {}
    failures = 0
    preempted = False
    for rec in records:
        if not isinstance(rec, dict):
            continue
        kind = rec.get("kind")
        if kind not in ("fault", "degrade", "retry", "failure",
                        "checkpoint"):
            continue
        if chosen is None:
            chosen = rec.get("run_id")
        if chosen is not None and rec.get("run_id") not in (None, chosen):
            continue
        if kind == "fault":
            faults.append(rec)
            if rec.get("fault_class") == "preemption":
                preempted = True
        elif kind == "degrade":
            degrades.append(rec)
        elif kind == "retry":
            cls = rec.get("fault_class") or "transient"
            retries_by_class[cls] = retries_by_class.get(cls, 0) + 1
        elif kind == "failure":
            failures += 1
        elif kind == "checkpoint" and rec.get("preempt"):
            preempted = True
    injected = [f for f in faults if f.get("injected")]
    real = [f for f in faults if not f.get("injected")]
    seams: dict = {}
    for f in faults:
        s = f.get("seam") or "?"
        seams[s] = seams.get(s, 0) + 1
    signals = {
        "faults_total": len(faults),
        "faults_injected": len(injected),
        "faults_real": len(real),
        "retries": sum(retries_by_class.values()),
        "retries_by_class": retries_by_class,
        "failures": failures,
        "degrade_steps": [d.get("ladder_step") for d in degrades],
        "fault_seams": seams,
    }
    flags = []

    def flag(name: str, detail: str) -> None:
        flags.append({"flag": name, "detail": detail})

    if failures:
        flag("failed", f"{failures} failure record(s): the run surfaced "
             "an unrecoverable fault — see the flight dump")
    if preempted:
        flag("preempted", "the platform reclaimed the machine; the run "
             "drained, checkpointed and exited with a resumable cursor")
    if degrades:
        steps = " -> ".join(str(s) for s in signals["degrade_steps"])
        flag("degraded",
             f"resource exhaustion stepped down the degradation ladder "
             f"({steps}): the run finished on a cheaper config — slower, "
             "never wrong (each step is bit-identity-tested)")
    if real:
        flag("fault-prone",
             f"{len(real)} real fault(s) classified at seams "
             f"{sorted({f.get('seam') for f in real})} and absorbed by "
             f"{signals['retries']} retr(ies) — watch the trend in the "
             "run-history warehouse")
    if injected:
        flag("chaos-tested",
             f"{len(injected)} injected fault(s) fired from the run's "
             "fault plan; results certified bit-identical when the "
             "retry budget absorbed them")
    fired = {f["flag"] for f in flags}
    verdict = next((v for v in RELIABILITY_ORDER if v in fired), "clean")
    return {"verdict": verdict, "flags": flags, "signals": signals}


def resolve_combiner(records: Iterable[dict]) -> str:
    """Resolve ``Config.combiner='auto'`` against a prior run's ledger
    (ISSUE 11): the most recent ``data`` record's verdict decides —
    skew-hot flips the hot-key combiner on, anything else (including no
    history at all) stays off.  The same flip the autotuner's
    ``skew-hot -> enable-combiner`` rule proposes.  NOTE (ISSUE 14):
    this is the jax-free PRIMITIVE; drivers resolve through
    ``obs/history.resolve_prior(records=...)["combiner"]`` — the one
    prior-run read — which reproduces this function bit-for-bit (the
    parity is asserted in the history selftest)."""
    rec = latest_data_record(records)
    if rec is None:
        return "off"
    return "hot-cache" if classify(rec)["verdict"] == "skew-hot" else "off"
