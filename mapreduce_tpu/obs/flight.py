"""Flight recorder: a bounded ring of recent events, dumped on failure.

Bench rounds 1-2 lost their perf record to an opaque relay wedge — the
process died (or was abandoned) with nothing on disk saying what it was
doing.  The flight recorder fixes the general case (ISSUE 2 tentpole (3)):
the executor records a tiny host-side event per dispatch / retry /
checkpoint into a fixed-size ring buffer, and the failure path dumps the
ring plus a state snapshot summary and the metrics-registry snapshot to a
JSON file, so a crashed or wedged run leaves forensics instead of nothing.

Recording cost is one deque.append of a small dict — host-only, no device
sync, O(1) memory (the ring evicts) — so it is safe to leave on for every
telemetered run.
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Optional


DEFAULT_CAPACITY = 256


class FlightRecorder:
    """Bounded event ring + one-shot crash dump."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self.events_recorded = 0  # total, including evicted
        self.dumped_to: Optional[str] = None

    def record(self, kind: str, **fields) -> None:
        self._ring.append({"ts": round(time.time(), 6), "kind": kind,
                           **fields})
        self.events_recorded += 1

    def events(self) -> list:
        return list(self._ring)

    def dump(self, path: str, context: Optional[dict] = None,
             state_summary: Optional[dict] = None,
             registry_snapshot: Optional[dict] = None,
             data: Optional[dict] = None,
             data_health: Optional[dict] = None) -> Optional[str]:
        """Write the forensics file; returns the path actually written, or
        ``None`` when the write failed (read-only/full filesystem) — a
        ledger failure record must not point at a dump that does not
        exist.  Idempotent per recorder: the first SUCCESSFUL dump owns
        the file (later failures in the same run would only overwrite the
        interesting one with unwind noise).  Best-effort by contract — a
        dump failure must never mask the run failure being reported."""
        if self.dumped_to is not None:
            return self.dumped_to
        payload = {
            "dumped_at": round(time.time(), 6),
            "context": context or {},
            "events_recorded": self.events_recorded,
            "events_kept": len(self._ring),
            "events": list(self._ring),
        }
        if state_summary is not None:
            payload["state"] = state_summary
        if registry_snapshot is not None:
            payload["metrics"] = registry_snapshot
        if data is not None:  # data-plane snapshot as of the crash (ISSUE 8)
            payload["data"] = data
        if data_health is not None:
            payload["data_health"] = data_health
        try:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=1, default=repr)
                f.write("\n")
        except OSError:
            return None  # the run failure itself still surfaces
        self.dumped_to = path
        return path


def summarize_state(state) -> dict:
    """Leaf-level summary of a host state pytree for the dump: shapes,
    dtypes, and byte sizes — enough to see WHAT was in flight without
    serializing a multi-GB accumulator into a crash file."""
    import jax
    import numpy as np

    leaves = jax.tree.leaves(state)
    out = {"n_leaves": len(leaves), "leaves": []}
    total = 0
    # total_nbytes covers EVERY leaf (it is what an OOM triage reads);
    # only the per-leaf detail list is capped to bound the dump size.
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        total += arr.nbytes
        if i < 64:
            out["leaves"].append({"shape": list(arr.shape),
                                  "dtype": str(arr.dtype),
                                  "nbytes": int(arr.nbytes)})
    out["total_nbytes"] = int(total)
    return out
