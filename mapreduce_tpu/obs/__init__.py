"""Unified telemetry for the streaming runtime (ISSUE 2).

One subsystem replacing three disconnected shims (``runtime/metrics.py``'s
timers, ``runtime/logging.py``'s event lines, ``runtime/profiling.py``'s
regions — all still used, now fed through one layer):

* :mod:`.registry` — process-wide counters/gauges/histograms with labels;
* :mod:`.ledger` — per-run JSONL step records (phase timings, bytes,
  device memory, compile events, retries);
* :mod:`.flight` — bounded ring of recent events, dumped with a state
  summary on the failure path;
* :mod:`.spans` — profiler-region + phase-timer spans so XProf timelines
  line up with ledger records;
* :mod:`.timeline` — jax-free reconstruction of per-group ``group``
  lifecycle records into per-resource timelines, overlap matrices,
  device-idle gap attribution and a critical-path ``bottleneck`` verdict
  (ISSUE 7); ``tools/trace_export.py`` renders the same records as
  Perfetto-viewable Chrome trace-event JSON;
* :mod:`.datahealth` — jax-free classification of the per-run ``data``
  record (on-device spill/rescue/skew/occupancy counters, ISSUE 8) into
  spill-bound / rescue-heavy / skew-hot / occupancy-starved /
  table-pressure verdicts — the data-shape fitness signal next to the
  timeline's resource verdict;
* :mod:`.fleet` — jax-free merge of multi-host per-process ledger shards
  (``<ledger>.h<p>.jsonl``, ISSUE 13) into one clock-aligned fleet
  timeline: per-host lanes, per-superstep straggler skew, collective
  accounting, and the ``fleet_bottleneck`` verdict (straggler-bound /
  collective-bound / balanced);
* :mod:`.history` — the run-history warehouse (ISSUE 14): ingest many
  (possibly sharded, append-mode) ledgers into an on-disk index of
  per-run digests keyed by config, longitudinal series/streak queries,
  the ``regressing``/``improving``/``steady``/``config-drift`` drift
  classifier, and :func:`.history.resolve_prior` — the one prior-run
  read ``combiner='auto'``, ``geometry='auto'`` and the autotuner's
  ``derive_signals`` resolve through;
* :mod:`.telemetry` — the facade the executor takes as ONE optional arg.

Reporting: ``tools/obs_report.py`` renders a ledger/flight pair into a run
summary with anomaly flags.  Schemas: ``docs/observability.md``.
"""

from mapreduce_tpu.obs import datahealth, fleet, history, timeline
from mapreduce_tpu.obs.flight import FlightRecorder, summarize_state
from mapreduce_tpu.obs.ledger import (LEDGER_VERSION, RunLedger, read_ledger,
                                      shard_flight_path, shard_path)
from mapreduce_tpu.obs.registry import MetricsRegistry, get_registry
from mapreduce_tpu.obs.spans import span
from mapreduce_tpu.obs.telemetry import (Telemetry, device_memory_stats,
                                         maybe)

__all__ = [
    "FlightRecorder", "LEDGER_VERSION", "MetricsRegistry", "RunLedger",
    "Telemetry", "datahealth", "device_memory_stats", "fleet",
    "get_registry", "history", "maybe", "read_ledger",
    "shard_flight_path", "shard_path", "span", "summarize_state",
    "timeline",
]
