"""Process-wide metrics registry: counters, gauges, histograms with labels.

The unified sink every layer writes into — executor dispatch, reader
prefetch, checkpoint writes, distributed init, collective strategy builds
(ISSUE 2 tentpole (1)).  All instruments are HOST-side dict updates under
one lock: nothing here ever touches a device, blocks on one, or appears in
a jitted program, so instrumented code keeps the async-dispatch pipeline
(the graphcheck host-sync pass stays green by construction).

Instruments:

* :class:`Counter` — monotonically increasing float/int (``inc``).
* :class:`Gauge` — last-write-wins value (``set``).
* :class:`Histogram` — fixed log-spaced buckets + count/sum/min/max
  (``observe``); sized for seconds-scale latencies (1 ms .. 60 s).

Labels: ``registry.counter("reader.batches", source="native")`` keys the
instrument by ``(name, sorted(labels))`` — the usual Prometheus shape,
flattened to ``name{k=v,...}`` in :meth:`MetricsRegistry.snapshot`.

A process-global default registry (:func:`get_registry`) serves the layers
that have no run-scoped handle (the reader's prefetch thread, module-level
collective builds); run-scoped telemetry (:class:`...obs.telemetry.Telemetry`)
binds to it by default so one snapshot carries everything.

The seconds-scale :data:`DEFAULT_BUCKETS` also carry the per-group
lifecycle observations the window retirement path emits (ISSUE 7):
``executor.groups_retired`` (counter), ``executor.group_device_seconds``
(dispatch-enqueue to observed token readiness) and
``executor.retire_wait_seconds`` (how long the retire actually blocked) —
the registry-side aggregate of what the ledger's ``group`` records carry
per group and ``obs/timeline.py`` reconstructs into lanes.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional, Tuple

# Log-spaced seconds buckets: 1 ms granularity at the bottom (a single fast
# dispatch), a minute at the top (a wedged-relay compile).  Upper bounds,
# inclusive; observations past the last bound land in +Inf.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 25.0, 60.0)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: dict) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _flat_name(name: str, key: _LabelKey) -> str:
    if not key:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in key) + "}"


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()  # prefetch thread + main loop both inc

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increments must be >= 0, got {amount}")
        with self._lock:
            self.value += amount


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)


class Histogram:
    __slots__ = ("bounds", "bucket_counts", "count", "sum", "min", "max",
                 "_lock")

    def __init__(self, bounds: Iterable[float] = DEFAULT_BUCKETS):
        self._lock = threading.Lock()
        self.bounds = tuple(bounds)
        if any(b <= a for a, b in zip(self.bounds, self.bounds[1:])):
            raise ValueError(f"bucket bounds must ascend: {self.bounds}")
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # +Inf overflow
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, value: float) -> None:
        v = float(value)
        i = 0
        for i, bound in enumerate(self.bounds):
            if v <= bound:
                break
        else:
            i = len(self.bounds)
        with self._lock:
            self.bucket_counts[i] += 1
            self.count += 1
            self.sum += v
            self.min = min(self.min, v)
            self.max = max(self.max, v)

    def as_dict(self) -> dict:
        return {
            "count": self.count,
            "sum": round(self.sum, 6),
            "min": round(self.min, 6) if self.count else None,
            "max": round(self.max, 6) if self.count else None,
            "buckets": {("+Inf" if i == len(self.bounds)
                         else repr(self.bounds[i])): c
                        for i, c in enumerate(self.bucket_counts) if c},
        }


class MetricsRegistry:
    """Thread-safe instrument store.  Instruments are created on first use
    and live for the registry's lifetime; a name must keep one kind (asking
    for ``counter("x")`` after ``gauge("x")`` is a programming error and
    raises)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, str, _LabelKey], object] = {}

    def _get(self, kind: str, name: str, labels: dict, factory):
        key = (kind, name, _label_key(labels))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                for other_kind, other_name, _ in self._instruments:
                    if other_name == name and other_kind != kind:
                        raise ValueError(
                            f"metric {name!r} already registered as "
                            f"{other_kind}, requested as {kind}")
                inst = self._instruments[key] = factory()
            return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels, Counter)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels, Gauge)

    def histogram(self, name: str,
                  buckets: Optional[Iterable[float]] = None,
                  **labels) -> Histogram:
        return self._get("histogram", name, labels,
                         lambda: Histogram(buckets or DEFAULT_BUCKETS))

    def observe(self, name: str, seconds: float, **labels) -> None:
        """Shorthand: one histogram observation (the common timing call)."""
        self.histogram(name, **labels).observe(seconds)

    def snapshot(self) -> dict:
        """One JSON-ready dict of everything recorded so far, stably keyed
        by flattened ``name{labels}``."""
        with self._lock:
            items = sorted(self._instruments.items(),
                           key=lambda kv: (kv[0][1], kv[0][2], kv[0][0]))
            out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
            for (kind, name, key), inst in items:
                flat = _flat_name(name, key)
                if kind == "counter":
                    v = inst.value
                    out["counters"][flat] = int(v) if v == int(v) else v
                elif kind == "gauge":
                    out["gauges"][flat] = inst.value
                else:
                    out["histograms"][flat] = inst.as_dict()
            return out

    def reset(self) -> None:
        """Drop every instrument (tests; a long-lived process between runs)."""
        with self._lock:
            self._instruments.clear()


_DEFAULT = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global default registry (see module docstring)."""
    return _DEFAULT
