"""Framework-wide constants.

The reference pins its scalability envelope with compile-time ``#define``s
(``main.cu:9-15``: GRID_SIZE/BLOCK_SIZE/MAX_INPUT_COUNT/...).  The TPU build
replaces those with *semantic* constants (separator classes, hash parameters,
sentinels) plus runtime-configurable capacities (see :mod:`mapreduce_tpu.config`).
Nothing here limits input size; shapes are chosen per-run and stay static only
within a compiled step.
"""

from __future__ import annotations

import numpy as np

# --- Separator byte classes -------------------------------------------------
# The reference tokenizes on space / CR / LF only (main.cu:188) and implicitly
# on NUL via memset padding (main.cu:178).  We add TAB (0x09) — a deliberate
# fix of the reference's "no tabs" quirk (SURVEY §2 defect 5) — and VT/FF for
# full C `isspace` semantics.  Keys remain case-sensitive and punctuation is
# preserved, matching the reference's intended semantics.
SEPARATOR_BYTES: tuple[int, ...] = (0x00, 0x09, 0x0A, 0x0B, 0x0C, 0x0D, 0x20)

# Byte used to pad chunk tensors to static shapes.  Must be a separator so
# padding can never extend or create a token.
PAD_BYTE: int = 0x00

# --- Rolling-hash parameters ------------------------------------------------
# Two independent 32-bit polynomial rolling hashes (odd bases, natural mod
# 2**32) form an effective 64-bit key.  Polynomial hashing is used because it
# has an *associative* segmented formulation (affine-function composition),
# which lets the whole tokenize+hash pass run as one `associative_scan` on the
# VPU instead of the per-thread char loops of the reference mapper
# (main.cu:37-54).
HASH_BASE_1 = np.uint32(16777619)  # FNV-1a 32-bit prime
HASH_BASE_2 = np.uint32(2654435761)  # Knuth multiplicative constant (odd)

# murmur3 fmix32 constants, used to finalize each 32-bit lane.
FMIX_C1 = np.uint32(0x85EBCA6B)
FMIX_C2 = np.uint32(0xC2B2AE35)

# --- Sentinels ---------------------------------------------------------------
# Empty slots in count tables and non-token positions in the per-byte stream
# carry the all-ones key; real keys are clamped one below it (a 2**-64 bias).
SENTINEL_KEY = np.uint32(0xFFFFFFFF)

# uint32 "infinity" used for first-occurrence position tracking (min-reduced).
POS_INF = np.uint32(0xFFFFFFFF)

# Length sentinel for cross-chunk n-gram table entries: the gram's true byte
# span ends in a LATER chunk whose row base the device cannot know, so the
# host recovers the span by scanning n tokens forward from the entry's
# absolute start offset (reader.scan_gram_length).  Real span lengths are
# bounded by the corpus size; the all-ones value cannot collide.
SEAM_GRAM_LENGTH = np.uint32(0xFFFFFFFF)
