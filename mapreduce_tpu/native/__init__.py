"""Native (C++) runtime components, loaded via ctypes.

The reference's runtime is native C++/CUDA (host pipeline + launch wrappers,
``main.cu:124-207``); the TPU build keeps the host-side data plane native too.
The library is compiled on first use from the bundled source (g++ is part of
the toolchain; there is no pybind11 in the image, so the ABI is plain C via
ctypes).  Every native entry point has a pure-Python fallback — absence of a
compiler degrades performance, never correctness.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from mapreduce_tpu import constants

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "chunker.cpp")
_LIB = os.path.join(_DIR, "_chunker.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

SEP_LUT = np.zeros(256, dtype=np.uint8)
for _b in constants.SEPARATOR_BYTES:
    SEP_LUT[_b] = 1


def _build() -> bool:
    # Compile to a private temp path and rename into place: an interrupted or
    # concurrent build must never leave a partial .so at the load path (a
    # truncated file with a fresh mtime would permanently disable the native
    # path for every later process).
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode == 0 and os.path.exists(tmp):
            os.replace(tmp, _LIB)
            return True
        return False
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def load() -> ctypes.CDLL | None:
    """The chunker library, building it on first call; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("MAPREDUCE_TPU_NO_NATIVE"):
            return None
        src_newer = (not os.path.exists(_LIB)
                     or os.path.getmtime(_SRC) > os.path.getmtime(_LIB))
        if src_newer and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        lib.mr_fill_batch.restype = ctypes.c_int64
        lib.mr_fill_batch.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, u8p, u8p, i64p, i64p]
        lib.mr_token_count.restype = ctypes.c_int64
        lib.mr_token_count.argtypes = [u8p, ctypes.c_int64, u8p]
        _lib = lib
        return _lib


def fill_batch(buf: np.ndarray, at_eof: bool, n_shards: int, chunk_bytes: int,
               max_token_bytes: int, out_data: np.ndarray,
               out_bases: np.ndarray, out_lengths: np.ndarray) -> int | None:
    """Native batch fill; returns consumed bytes, or None if lib unavailable."""
    lib = load()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf)
    return int(lib.mr_fill_batch(
        buf, buf.shape[0], int(at_eof), n_shards, chunk_bytes,
        max_token_bytes, SEP_LUT, out_data, out_bases, out_lengths))


def token_count(buf: np.ndarray) -> int | None:
    """Native exact token count, or None if lib unavailable."""
    lib = load()
    if lib is None:
        return None
    buf = np.ascontiguousarray(buf)
    return int(lib.mr_token_count(buf, buf.shape[0], SEP_LUT))
