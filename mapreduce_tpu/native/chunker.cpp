// Native ingest chunker: boundary-aligned batch filling for the streaming
// executor.  C++ counterpart of mapreduce_tpu/data/reader.py's Python path
// (which replaces the reference's fgets/char-scan host pipeline,
// main.cu:166-207).  The hot host work per step — finding separator-aligned
// cut points and packing rows into the pinned [n_shards, chunk_bytes] batch
// buffer — runs here as straight memcpy/scan loops the compiler vectorizes,
// keeping the feeding thread off the Python interpreter for 100GB-scale runs.
//
// Contract (mirrors reader._aligned_cuts exactly; tests assert parity):
//   * a row may only end at a separator byte, so no token spans rows;
//   * if no separator exists in the trailing max_token_bytes window, the row
//     is force-split at the ideal cut (overlong-run guard);
//   * only the true end of file may cut mid-token (at_eof).
//
// Built as a plain shared library, loaded via ctypes (no pybind11 in the
// image); all buffers are caller-allocated numpy arrays.

#include <cstdint>
#include <cstring>

extern "C" {

// Fill one streaming batch.  Returns bytes consumed from buf (== last cut).
//
//   buf/buf_len: the window of the corpus starting at the current offset.
//   at_eof:      nonzero when buf reaches the true end of the file.
//   sep_lut:     256-entry table, nonzero for separator bytes.
//   out_data:    [n_shards * chunk_bytes], fully overwritten (pad = 0).
//   out_bases:   [n_shards] row start offsets relative to buf.
//   out_lengths: [n_shards] valid bytes per row.
int64_t mr_fill_batch(const uint8_t* buf, int64_t buf_len, int at_eof,
                      int64_t n_shards, int64_t chunk_bytes,
                      int64_t max_token_bytes, const uint8_t* sep_lut,
                      uint8_t* out_data, int64_t* out_bases,
                      int64_t* out_lengths) {
  int64_t prev = 0;
  for (int64_t i = 0; i < n_shards; ++i) {
    int64_t cut;
    int64_t ideal = prev + chunk_bytes;
    if (ideal > buf_len) ideal = buf_len;
    if (ideal >= buf_len && at_eof) {
      cut = buf_len;
    } else {
      int64_t lo = ideal - max_token_bytes;
      if (lo < prev) lo = prev;
      cut = ideal;  // force-split when the window has no separator
      for (int64_t j = ideal - 1; j >= lo; --j) {
        if (sep_lut[buf[j]]) {
          cut = j + 1;
          break;
        }
      }
    }
    int64_t len = cut - prev;
    uint8_t* row = out_data + i * chunk_bytes;
    if (len > 0) std::memcpy(row, buf + prev, static_cast<size_t>(len));
    if (len < chunk_bytes)
      std::memset(row + len, 0, static_cast<size_t>(chunk_bytes - len));
    out_bases[i] = prev;
    out_lengths[i] = len;
    prev = cut;
  }
  return prev;
}

// Exact token count of a buffer (host-side oracle / metrics helper): the
// number of non-separator runs.  The buffer end counts as a separator.
int64_t mr_token_count(const uint8_t* buf, int64_t n, const uint8_t* sep_lut) {
  int64_t count = 0;
  int in_token = 0;
  for (int64_t i = 0; i < n; ++i) {
    int sep = sep_lut[buf[i]];
    count += in_token & sep;
    in_token = !sep;
  }
  return count + in_token;
}

}  // extern "C"
